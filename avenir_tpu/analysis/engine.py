"""The analysis engine: shared-parse corpus, rule registry, findings.

Every rule used to re-walk the package with its own ``os.walk`` +
``ast.parse`` loop (four coverage test modules, ~900 lines); here the
package is parsed ONCE into a :class:`Corpus` and every registered rule
checks the shared trees.  Rules return structured :class:`Finding` s so
one CLI (``python -m avenir_tpu analyze``) and one tier-1 test can run
the whole catalog with text or JSON output.
"""

from __future__ import annotations

import ast
import json
import os
import time
from typing import Callable, Dict, List, Optional, Sequence


class SourceFile:
    """One parsed package module (parse happens once, in Corpus)."""

    __slots__ = ("rel", "path", "text", "tree")

    def __init__(self, rel: str, path: str, text: str, tree: ast.AST):
        self.rel = rel          # package-relative, e.g. "core/io.py"
        self.path = path
        self.text = text
        self.tree = tree


class Corpus:
    """Every ``.py`` under one root, parsed once and shared by all
    rules.  ``readme`` is the documentation surface the config-key rule
    checks (None = no README check).  ``parse_cache`` optionally maps a
    per-file cache key (see :mod:`.cache`) to an already-parsed tree so
    a warm run skips re-parsing unchanged files."""

    def __init__(self, root: str, readme_path: Optional[str] = None,
                 parse_cache: Optional[dict] = None):
        self.root = root
        self.readme_path = readme_path
        self.files: Dict[str, SourceFile] = {}
        self._readme: Optional[str] = None
        self._dataflow: Optional["Dataflow"] = None
        self.parsed_files = 0      # files actually ast.parse'd this load
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__")
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path) as fh:
                    text = fh.read()
                tree = None
                if parse_cache is not None:
                    cached = parse_cache.get(rel)
                    if cached is not None and cached[0] == text:
                        tree = cached[1]
                if tree is None:
                    tree = ast.parse(text, filename=path)
                    self.parsed_files += 1
                self.files[rel] = SourceFile(rel, path, text, tree)

    def dataflow(self) -> "Dataflow":
        """The corpus's interprocedural dataflow index, built once and
        shared by every rule that needs reachability (fold-purity,
        carry-portability)."""
        if self._dataflow is None:
            self._dataflow = Dataflow(self)
        return self._dataflow

    @property
    def readme(self) -> str:
        if self._readme is None:
            if self.readme_path and os.path.exists(self.readme_path):
                with open(self.readme_path) as fh:
                    self._readme = fh.read()
            else:
                self._readme = ""
        return self._readme

    def get(self, rel: str) -> Optional[SourceFile]:
        return self.files.get(rel)

    def items(self):
        return sorted(self.files.items())


class Finding:
    """One structured rule violation.

    ``tag`` subdivides a rule's findings: ``violation`` (the rule's own
    check), ``stale-exclusion`` (a registry entry whose site no longer
    exists or no longer violates), ``empty-reason`` (a registry entry
    without a written reason).  All three fail ``--strict``."""

    __slots__ = ("rule", "file", "line", "message", "hint", "tag")

    def __init__(self, rule: str, file: str, line: int, message: str,
                 hint: str = "", tag: str = "violation"):
        self.rule = rule
        self.file = file
        self.line = int(line)
        self.message = message
        self.hint = hint
        self.tag = tag

    def format(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        s = f"{self.rule}  {loc}  {self.message}"
        if self.hint:
            s += f"  [fix: {self.hint}]"
        return s

    def to_dict(self) -> dict:
        return {"rule": self.rule, "file": self.file, "line": self.line,
                "message": self.message, "hint": self.hint,
                "tag": self.tag}

    def __repr__(self):
        return f"Finding({self.format()!r})"


class Rule:
    """One registered check: ``fn(corpus) -> [Finding]``.

    ``scope`` is ``"source"`` for pure-AST rules (they run on any
    corpus, including test fixtures) or ``"project"`` for rules that
    import the real package (driver registry introspection) and only
    make sense against the installed ``avenir_tpu``."""

    __slots__ = ("id", "doc", "fn", "scope")

    def __init__(self, rule_id: str, doc: str,
                 fn: Callable[[Corpus], List[Finding]],
                 scope: str = "source"):
        if scope not in ("source", "project"):
            raise ValueError(f"bad rule scope: {scope!r}")
        self.id = rule_id
        self.doc = doc
        self.fn = fn
        self.scope = scope

    def check(self, corpus: Corpus) -> List[Finding]:
        return self.fn(corpus)


RULES: Dict[str, Rule] = {}


def rule(rule_id: str, doc: str, scope: str = "source"):
    """Decorator registering ``fn(corpus) -> [Finding]`` under a stable
    rule id (the id findings, exclusions, and ``--rules`` name)."""
    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id: {rule_id}")
        RULES[rule_id] = Rule(rule_id, doc, fn, scope)
        return fn
    return deco


def all_rule_ids() -> List[str]:
    return sorted(RULES)


_PACKAGE_CORPUS: Optional[Corpus] = None


def load_package_corpus(fresh: bool = False) -> Corpus:
    """The corpus every default run analyzes: the installed
    ``avenir_tpu`` package, with the repo README as the doc surface.
    Cached per process (one parse feeds the CLI, the tier-1 wrapper,
    and every coverage shim); ``fresh=True`` re-parses."""
    global _PACKAGE_CORPUS
    if _PACKAGE_CORPUS is None or fresh:
        import avenir_tpu
        pkg = os.path.dirname(os.path.abspath(avenir_tpu.__file__))
        _PACKAGE_CORPUS = Corpus(pkg, readme_path=os.path.join(
            os.path.dirname(pkg), "README.md"))
    return _PACKAGE_CORPUS


def run_rules(corpus: Corpus,
              rule_ids: Optional[Sequence[str]] = None,
              scopes: Sequence[str] = ("source", "project")):
    """Run the selected rules over one shared corpus.

    Returns ``(findings, report)`` where ``report`` is the JSON-ready
    run summary (per-rule finding counts and durations)."""
    if rule_ids is None:
        selected = [RULES[r] for r in all_rule_ids()
                    if RULES[r].scope in scopes]
    else:
        unknown = sorted(set(rule_ids) - set(RULES))
        if unknown:
            raise KeyError(
                f"unknown rule id(s): {unknown}; known: {all_rule_ids()}")
        selected = [RULES[r] for r in rule_ids]
    findings: List[Finding] = []
    per_rule = []
    t0 = time.monotonic()
    for r in selected:
        rt0 = time.monotonic()
        got = r.check(corpus)
        findings.extend(got)
        per_rule.append({"rule": r.id, "findings": len(got),
                         "ms": round((time.monotonic() - rt0) * 1e3, 2)})
    # deterministic (file, line, rule) order: reports diff stably across
    # runs and machines, and a file's findings read top-to-bottom
    findings.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    report = {"root": corpus.root,
              "files": len(corpus.files),
              "rules": per_rule,
              "findings": [f.to_dict() for f in findings],
              "total_findings": len(findings),
              "duration_ms": round((time.monotonic() - t0) * 1e3, 2)}
    return findings, report


def write_json_report(path: str, report: dict) -> None:
    """Atomic JSON findings report (the CI artifact)."""
    from ..core.io import atomic_write_text
    atomic_write_text(path, json.dumps(report, indent=2) + "\n")


# ---------------------------------------------------------------------------
# shared AST helpers (used by several rule modules)
# ---------------------------------------------------------------------------

class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor tracking the enclosing class/function qualname stack
    (the ``Class.method`` / ``func.<locals>`` naming the legacy walkers
    used)."""

    def __init__(self):
        self.stack: List[str] = []

    def qual(self) -> str:
        return ".".join(self.stack) if self.stack else "<module>"

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


# ---------------------------------------------------------------------------
# the light interprocedural dataflow pass
# ---------------------------------------------------------------------------

#: attribute-call names too generic to resolve by name (they would edge
#: into unrelated same-file classes: dict/list/str verbs, context hooks)
_ATTR_STOPLIST = frozenset({
    "get", "put", "set", "items", "keys", "values", "append", "add",
    "pop", "update", "extend", "remove", "clear", "join", "split",
    "strip", "format", "read", "write", "close", "open", "copy",
    "start", "stop", "run", "wait", "notify", "acquire", "release",
    "setdefault", "sort", "count", "index", "startswith", "endswith",
})

#: method names that mutate their receiver (a call ``G.append(...)`` on
#: a module global marks the global mutable)
_MUTATOR_METHODS = frozenset({
    "append", "add", "pop", "update", "extend", "remove", "clear",
    "setdefault", "insert", "popleft", "appendleft", "discard",
})


def dotted_name(node) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


class FunctionInfo:
    """One function/method's def-use summary: the calls it makes, the
    ``self.*`` attributes and module globals it reads/writes, and its
    AST node (rules walk the body for their own site patterns)."""

    __slots__ = ("rel", "qual", "node", "calls", "self_reads",
                 "self_writes", "global_reads", "global_writes")

    def __init__(self, rel: str, qual: str, node):
        self.rel = rel
        self.qual = qual
        self.node = node
        #: (kind, base, name) with kind in {bare, self, mod, attr}
        self.calls: List[tuple] = []
        self.self_reads: set = set()
        self.self_writes: set = set()
        self.global_reads: set = set()
        self.global_writes: set = set()


class _ModuleIndex:
    """Per-module symbol tables feeding the call graph: functions by
    qualname, classes with their method names, module globals (and the
    mutable subset), and import resolution back into the corpus."""

    def __init__(self, corpus: "Corpus", rel: str, tree):
        self.rel = rel
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, set] = {}
        self.class_lines: Dict[str, int] = {}
        self.class_bases: Dict[str, List[str]] = {}
        self.module_globals: set = set()
        self.mutated_globals: set = set()
        self.mutable_literal_globals: set = set()
        self.escaped_globals: set = set()
        self.mod_imports: Dict[str, str] = {}     # alias -> corpus rel
        self.from_imports: Dict[str, tuple] = {}  # name -> (rel, orig)
        self._collect_toplevel(tree)
        self._collect_imports(corpus, tree)
        self._collect_functions(tree)

    def effectively_mutable_globals(self) -> set:
        """Module globals whose reads are nondeterministic process
        state: mutated in-module (rebind/subscript/mutator call), or
        bound to a mutable container that escapes into a call (the
        pass-by-reference cache idiom) — a read-only constant dict
        stays pure."""
        return self.mutated_globals | (self.mutable_literal_globals
                                       & self.escaped_globals)

    # -- import resolution -------------------------------------------------
    def _resolve_rel(self, corpus, level: int, module: Optional[str],
                     name: Optional[str] = None):
        """Corpus rel path of a relative/absolute import target (the
        module itself, or ``module/name`` when ``name`` is a submodule);
        returns ``(rel_or_None, name_is_module)``."""
        parts = self.rel.split("/")[:-1]          # importing pkg path
        if level > 0:
            base = parts[:len(parts) - (level - 1)] if level > 1 else parts
        else:
            mod_parts = (module or "").split(".")
            # absolute import of this package: strip the package root
            if mod_parts and mod_parts[0] == "avenir_tpu":
                mod_parts = mod_parts[1:]
                base = []
                module = ".".join(mod_parts)
            else:
                return None, False
        target = base + ([p for p in module.split(".") if p]
                         if module else [])

        def file_of(p):
            for cand in ("/".join(p) + ".py",
                         "/".join(p) + "/__init__.py" if p else None):
                if cand and cand in corpus.files:
                    return cand
            return None

        if name is not None:
            sub = file_of(target + [name])
            if sub is not None:
                return sub, True
        return file_of(target), False

    def _collect_imports(self, corpus, tree) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            for alias in node.names:
                local = alias.asname or alias.name
                rel, is_mod = self._resolve_rel(
                    corpus, node.level, node.module, alias.name)
                if rel is None:
                    continue
                if is_mod:
                    self.mod_imports[local] = rel
                else:
                    self.from_imports[local] = (rel, alias.name)

    # -- symbol tables -----------------------------------------------------
    def _collect_toplevel(self, tree) -> None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                methods = set()
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        methods.add(sub.name)
                self.classes[node.name] = methods
                self.class_lines[node.name] = node.lineno
                self.class_bases[node.name] = [
                    b for b in (dotted_name(base) for base in node.bases)
                    if b]
            elif isinstance(node, (ast.Assign, ast.AnnAssign,
                                   ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = getattr(node, "value", None)
                mutable = isinstance(value, (ast.Dict, ast.List,
                                             ast.Set))
                if isinstance(value, ast.Call):
                    ctor = dotted_name(value.func) or ""
                    mutable = ctor.rsplit(".", 1)[-1] in (
                        "dict", "list", "set", "deque", "defaultdict",
                        "OrderedDict", "Counter")
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.module_globals.add(t.id)
                        if mutable:
                            self.mutable_literal_globals.add(t.id)

    def _collect_functions(self, tree) -> None:
        idx = self

        class Walk(ScopedVisitor):
            def __init__(self):
                super().__init__()
                self.fn_stack: List[FunctionInfo] = []

            def visit_ClassDef(self, node):
                # class BODIES execute at import time: statements like
                # `LANES = jax.device_count()` must be visible to the
                # reachability rules, so each class gets a synthetic
                # `<Cls>.<class>` scope (methods stay separate nodes —
                # defining one is not calling one)
                self.stack.append(node.name)
                info = FunctionInfo(idx.rel,
                                    f"{self.qual()}.<class>", node)
                idx.functions[info.qual] = info
                self.fn_stack.append(info)
                self.generic_visit(node)
                self.fn_stack.pop()
                self.stack.pop()

            def visit_FunctionDef(self, node):
                self.stack.append(node.name)
                info = FunctionInfo(idx.rel, self.qual(), node)
                idx.functions[info.qual] = info
                if (self.fn_stack
                        and not self.fn_stack[-1].qual.endswith(
                            ".<class>")):
                    # lexically nested defs run in the parent's context
                    # (callbacks, closures): an implicit call edge keeps
                    # them reachable whenever the parent is
                    self.fn_stack[-1].calls.append(
                        ("nested", None, info.qual))
                self.fn_stack.append(info)
                self.generic_visit(node)
                self.fn_stack.pop()
                self.stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def _info(self):
                return self.fn_stack[-1] if self.fn_stack else None

            def visit_Global(self, node):
                info = self._info()
                if info is not None:
                    info.global_writes.update(node.names)
                    idx.mutated_globals.update(node.names)
                self.generic_visit(node)

            def visit_Attribute(self, node):
                info = self._info()
                if (info is not None and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    if isinstance(node.ctx, ast.Load):
                        info.self_reads.add(node.attr)
                    else:
                        info.self_writes.add(node.attr)
                self.generic_visit(node)

            def visit_Name(self, node):
                info = self._info()
                if info is not None and node.id in idx.module_globals:
                    if isinstance(node.ctx, ast.Load):
                        info.global_reads.add(node.id)
                    else:
                        info.global_writes.add(node.id)
                        idx.mutated_globals.add(node.id)
                self.generic_visit(node)

            def visit_Subscript(self, node):
                # G[k] = v / del G[k] on a module global mutates it
                if (not isinstance(node.ctx, ast.Load)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in idx.module_globals):
                    idx.mutated_globals.add(node.value.id)
                self.generic_visit(node)

            def visit_Call(self, node):
                info = self._info()
                fn = node.func
                for arg in node.args:
                    # a module global handed to a call escapes: the
                    # callee may mutate the container (the pass-by-
                    # reference cache idiom)
                    if (isinstance(arg, ast.Name)
                            and arg.id in idx.module_globals):
                        idx.escaped_globals.add(arg.id)
                if info is not None:
                    if isinstance(fn, ast.Name):
                        info.calls.append(("bare", None, fn.id))
                    elif isinstance(fn, ast.Attribute):
                        base = fn.value
                        if isinstance(base, ast.Name):
                            if base.id == "self":
                                info.calls.append(("self", None, fn.attr))
                            else:
                                info.calls.append(("mod", base.id,
                                                   fn.attr))
                                # G.append(...) on a module global
                                if (base.id in idx.module_globals
                                        and fn.attr in _MUTATOR_METHODS):
                                    idx.mutated_globals.add(base.id)
                        else:
                            info.calls.append(("attr", None, fn.attr))
                self.generic_visit(node)

        Walk().visit(tree)


class Dataflow:
    """The corpus-wide call graph + def-use index: per-function
    summaries (:class:`FunctionInfo`) and one-level call resolution —
    bare names to same-module functions/classes and from-imported corpus
    functions, ``self.m`` to the enclosing class, ``alias.f`` through
    resolved module imports, and ``obj.m`` by unique method name within
    the module (a deliberate over-approximation; generic verbs on
    :data:`_ATTR_STOPLIST` never resolve).  :meth:`reachable` closes
    over those edges — the substrate for the distributed-readiness
    rules (fold-purity, carry-portability)."""

    def __init__(self, corpus: "Corpus"):
        self.corpus = corpus
        self.modules: Dict[str, _ModuleIndex] = {
            rel: _ModuleIndex(corpus, rel, sf.tree)
            for rel, sf in corpus.items()}
        self._callees: Dict[tuple, set] = {}

    def function(self, rel: str, qual: str) -> Optional[FunctionInfo]:
        idx = self.modules.get(rel)
        return idx.functions.get(qual) if idx else None

    def expand_prefixes(self, rel: str,
                        prefixes: Sequence[str]) -> List[tuple]:
        """Every (rel, qual) whose qualname equals a prefix or nests
        under it (``prefix.<inner>``)."""
        idx = self.modules.get(rel)
        if idx is None:
            return []
        out = []
        for qual in idx.functions:
            for p in prefixes:
                if qual == p or qual.startswith(p + "."):
                    out.append((rel, qual))
                    break
        return out

    def callees(self, key: tuple) -> set:
        if key in self._callees:
            return self._callees[key]
        rel, qual = key
        idx = self.modules.get(rel)
        info = idx.functions.get(qual) if idx else None
        out: set = set()
        if info is not None:
            cls = qual.split(".")[0] if "." in qual else None
            for kind, base, name in info.calls:
                if kind == "nested":
                    out.add((rel, name))
                elif kind == "self" and cls in idx.classes:
                    if name in idx.classes[cls]:
                        out.add((rel, f"{cls}.{name}"))
                elif kind == "bare":
                    if name in idx.functions:
                        out.add((rel, name))
                    elif (name in idx.classes
                          and "__init__" in idx.classes[name]):
                        out.add((rel, f"{name}.__init__"))
                    elif name in idx.from_imports:
                        trel, orig = idx.from_imports[name]
                        tidx = self.modules.get(trel)
                        if tidx and orig in tidx.functions:
                            out.add((trel, orig))
                        elif (tidx and orig in tidx.classes
                              and "__init__" in tidx.classes[orig]):
                            out.add((trel, f"{orig}.__init__"))
                elif kind == "mod":
                    if base in idx.mod_imports:
                        trel = idx.mod_imports[base]
                        tidx = self.modules.get(trel)
                        if tidx and name in tidx.functions:
                            out.add((trel, name))
                elif kind == "attr" and name not in _ATTR_STOPLIST:
                    owners = [c for c, ms in idx.classes.items()
                              if name in ms]
                    if len(owners) == 1:
                        out.add((rel, f"{owners[0]}.{name}"))
        self._callees[key] = out
        return out

    def reachable(self, roots: Sequence[tuple],
                  max_depth: Optional[int] = None) -> set:
        """BFS closure of (rel, qual) keys over resolved call edges
        (``max_depth`` bounds the hop count from the roots; None =
        transitive closure)."""
        seen = set()
        frontier = [r for r in roots
                    if self.function(*r) is not None]
        seen.update(frontier)
        depth = 0
        while frontier and (max_depth is None or depth < max_depth):
            nxt = []
            for key in frontier:
                for callee in self.callees(key):
                    if callee not in seen:
                        seen.add(callee)
                        nxt.append(callee)
            frontier = nxt
            depth += 1
        return seen


def enclosing_scope_source(text: str, lineno: int, tree=None) -> str:
    """Source of the innermost function/class whose body spans
    ``lineno`` (1-based) — the scope a required call must live in.
    Pass the SourceFile's already-parsed ``tree`` to honor the
    one-parse-per-file contract; the re-parse is a fallback for raw
    text."""
    if tree is None:
        tree = ast.parse(text)
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.lineno <= lineno <= (node.end_lineno or node.lineno):
                if best is None or node.lineno > best.lineno:
                    best = node
    if best is None:
        return text
    return "\n".join(text.splitlines()[best.lineno - 1:best.end_lineno])

"""``python -m avenir_tpu analyze``: run the rule catalog over the
package.

Usage::

    python -m avenir_tpu analyze [--strict] [--json report.json]
                                 [--rules id1,id2] [--list] [--no-cache]
                                 [--baseline findings.json]
                                 [--update-baseline]
                                 [--dynamic] [--seeds N]

- default: print findings as text lines (``rule  file:line  message``)
  plus a one-line summary; exit 0 regardless of findings.  Warm runs
  are incremental: unchanged files are never re-parsed and an unchanged
  corpus replays the previous findings (sidecar under
  ``.avenir-analyze/``; ``--no-cache`` forces a cold run).
- ``--strict``: exit 1 when any unexcluded finding (including stale
  exclusions / empty reasons) survives — the CI gate.  With
  ``--baseline`` only NEW findings (absent from the baseline) fail.
- ``--json <path>``: also write the machine-readable findings report
  (atomic publish, the CI artifact; includes per-rule wall time and
  finding counts, findings sorted (file, line, rule)).
- ``--rules a,b``: run a subset of the catalog.
- ``--list``: print the rule catalog (id, scope, doc) and exit.
- ``--baseline <path>``: ratchet mode — diff findings against the
  committed baseline and fail only on new ones, so a new rule can land
  before its cleanups finish.  ``--update-baseline`` rewrites the
  baseline atomically from the current findings.
- ``--dynamic``: after the static catalog, run the fold-algebra
  split-invariance verifier (core.algebra) over every registered
  FoldSpec and the snapshot/histogram merges; any failed property
  exits 1 regardless of ``--strict``.  ``--seeds N`` controls how many
  seeds each property runs under (default 3).
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from .engine import (RULES, all_rule_ids, load_package_corpus, run_rules,
                     write_json_report)


def _finding_key(d: dict) -> tuple:
    """Baseline identity for one finding: line numbers drift with
    unrelated edits, so the ratchet matches on stable content."""
    return (d["rule"], d["file"], d["message"], d.get("tag", "violation"))


def _load_baseline(path: str) -> Optional[List[dict]]:
    try:
        with open(path) as fh:
            data = json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        raise SystemExit(f"analyze: unreadable baseline {path}: {exc}")
    if isinstance(data, dict):
        return list(data.get("findings", []))
    raise SystemExit(f"analyze: baseline {path} is not a findings dict")


def analyze_main(argv: List[str]) -> int:
    strict = False
    json_out: Optional[str] = None
    rule_ids = None
    list_rules = False
    use_cache = True
    baseline_path: Optional[str] = None
    update_baseline = False
    dynamic = False
    n_seeds = 3
    i = 0
    while i < len(argv):
        a = argv[i]

        def value(flag):
            nonlocal i
            if a.startswith(flag + "="):
                v = a.partition("=")[2]
            else:
                i += 1
                v = argv[i] if i < len(argv) else ""
            if not v or v.startswith("--"):
                # a following flag is NOT a value: `--baseline
                # --update-baseline` must be a usage error, not a
                # baseline file literally named "--update-baseline"
                print(f"{flag} requires a value", file=sys.stderr)
                raise SystemExit(2)
            return v

        try:
            if a == "--strict":
                strict = True
            elif a == "--list":
                list_rules = True
            elif a == "--no-cache":
                use_cache = False
            elif a == "--dynamic":
                dynamic = True
            elif a == "--update-baseline":
                update_baseline = True
            elif a == "--json" or a.startswith("--json="):
                json_out = value("--json")
            elif a == "--baseline" or a.startswith("--baseline="):
                baseline_path = value("--baseline")
            elif a == "--seeds" or a.startswith("--seeds="):
                try:
                    n_seeds = int(value("--seeds"))
                except ValueError:
                    print("--seeds requires an integer", file=sys.stderr)
                    return 2
                if n_seeds < 1:
                    print("--seeds must be >= 1", file=sys.stderr)
                    return 2
            elif a == "--rules" or a.startswith("--rules="):
                spec = value("--rules")
                rule_ids = [r.strip() for r in spec.split(",")
                            if r.strip()]
            else:
                print(f"unknown analyze option: {a}", file=sys.stderr)
                return 2
        except SystemExit as exc:
            if isinstance(exc.code, int):
                return exc.code
            raise
        i += 1

    if update_baseline and not baseline_path:
        print("--update-baseline requires --baseline <path>",
              file=sys.stderr)
        return 2

    if list_rules:
        for rid in all_rule_ids():
            r = RULES[rid]
            print(f"{rid:18s} [{r.scope}] {r.doc}")
        return 0

    try:
        if use_cache:
            from .cache import cached_package_run
            findings, report = cached_package_run(rule_ids=rule_ids)
        else:
            findings, report = run_rules(load_package_corpus(),
                                         rule_ids=rule_ids)
            report["cached"] = False
    except KeyError as exc:
        print(f"analyze: {exc.args[0]}", file=sys.stderr)
        return 2

    for f in findings:
        print(f.format())
    ran = len(report["rules"])
    cached = " (cached)" if report.get("cached") else ""
    print(f"analyze: {len(findings)} finding(s) from {ran} rule(s) over "
          f"{report['files']} file(s) in {report['duration_ms']:.0f} ms"
          f"{cached}", file=sys.stderr)

    # -- baseline ratchet --------------------------------------------------
    gate_findings = findings
    if baseline_path:
        current = [f.to_dict() for f in findings]
        if update_baseline:
            from ..core.io import atomic_write_text
            atomic_write_text(baseline_path, json.dumps(
                {"findings": current}, indent=2) + "\n")
            print(f"analyze: baseline updated with {len(current)} "
                  f"finding(s) at {baseline_path}", file=sys.stderr)
            gate_findings = []
        else:
            base = _load_baseline(baseline_path)
            if base is None:
                print(f"analyze: no baseline at {baseline_path} "
                      f"(treating every finding as new; write one with "
                      f"--update-baseline)", file=sys.stderr)
                base = []
            # multiset diff: a SECOND identical violation in the same
            # file (several rules emit line-independent messages) must
            # not hide behind one baselined occurrence
            from collections import Counter
            known = Counter(_finding_key(d) for d in base)
            seen: Counter = Counter()
            new = []
            for f in findings:
                k = _finding_key(f.to_dict())
                seen[k] += 1
                if seen[k] > known.get(k, 0):
                    new.append(f)
            resolved = sum((known - seen).values())
            print(f"analyze: baseline ratchet — {len(new)} new, "
                  f"{len(findings) - len(new)} known, "
                  f"{resolved} resolved (baseline has "
                  f"{len(base)})", file=sys.stderr)
            gate_findings = new
            report["baseline"] = {
                "path": baseline_path, "known": len(base),
                "new": len(new), "resolved": resolved}

    # -- dynamic fold-algebra verification ---------------------------------
    dynamic_failed = False
    if dynamic:
        from ..cli import _init_runtime
        _init_runtime()
        from ..core.algebra import DEFAULT_SEEDS, run_dynamic
        seeds = (list(DEFAULT_SEEDS) + [101 + 13 * k
                                        for k in range(n_seeds)])[:n_seeds]
        reports = run_dynamic(
            seeds=seeds, log=lambda m: print(m, file=sys.stderr))
        failed = [r for r in reports if r.failed]
        dynamic_failed = bool(failed)
        report["dynamic"] = [r.to_dict() for r in reports]
        print(f"analyze: dynamic verification — "
              f"{len(reports) - len(failed)}/{len(reports)} report(s) "
              f"clean", file=sys.stderr)
        for r in failed:
            print(r.format(), file=sys.stderr)

    if json_out:
        write_json_report(json_out, report)
        print(f"analyze: wrote JSON report to {json_out}",
              file=sys.stderr)
    if dynamic_failed:
        return 1
    if strict and gate_findings:
        return 1
    return 0

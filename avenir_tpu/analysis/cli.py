"""``python -m avenir_tpu analyze``: run the rule catalog over the
package.

Usage::

    python -m avenir_tpu analyze [--strict] [--json report.json]
                                 [--rules id1,id2] [--list]

- default: print findings as text lines (``rule  file:line  message``)
  plus a one-line summary; exit 0 regardless of findings.
- ``--strict``: exit 1 when any unexcluded finding (including stale
  exclusions / empty reasons) survives — the CI gate.
- ``--json <path>``: also write the machine-readable findings report
  (atomic publish, the CI artifact).
- ``--rules a,b``: run a subset of the catalog.
- ``--list``: print the rule catalog (id, scope, doc) and exit.
"""

from __future__ import annotations

import sys
from typing import List, Optional

from .engine import (RULES, all_rule_ids, load_package_corpus, run_rules,
                     write_json_report)


def analyze_main(argv: List[str]) -> int:
    strict = False
    json_out: Optional[str] = None
    rule_ids = None
    list_rules = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--strict":
            strict = True
        elif a == "--list":
            list_rules = True
        elif a == "--json" or a.startswith("--json="):
            if "=" in a:
                json_out = a.partition("=")[2]
            else:
                i += 1
                if i >= len(argv):
                    print("--json requires a path", file=sys.stderr)
                    return 2
                json_out = argv[i]
            if not json_out:
                print("--json requires a path", file=sys.stderr)
                return 2
        elif a == "--rules" or a.startswith("--rules="):
            if "=" in a:
                spec = a.partition("=")[2]
            else:
                i += 1
                if i >= len(argv):
                    print("--rules requires a comma-separated list",
                          file=sys.stderr)
                    return 2
                spec = argv[i]
            rule_ids = [r.strip() for r in spec.split(",") if r.strip()]
        else:
            print(f"unknown analyze option: {a}", file=sys.stderr)
            return 2
        i += 1

    if list_rules:
        for rid in all_rule_ids():
            r = RULES[rid]
            print(f"{rid:18s} [{r.scope}] {r.doc}")
        return 0

    corpus = load_package_corpus()
    try:
        findings, report = run_rules(corpus, rule_ids=rule_ids)
    except KeyError as exc:
        print(f"analyze: {exc.args[0]}", file=sys.stderr)
        return 2
    for f in findings:
        print(f.format())
    ran = len(report["rules"])
    print(f"analyze: {len(findings)} finding(s) from {ran} rule(s) over "
          f"{report['files']} file(s) in {report['duration_ms']:.0f} ms",
          file=sys.stderr)
    if json_out:
        write_json_report(json_out, report)
        print(f"analyze: wrote JSON report to {json_out}",
              file=sys.stderr)
    if strict and findings:
        return 1
    return 0

"""CLI job driver: the reference's user surface, JVM-free.

The reference runs every job as
``hadoop jar avenir-1.0.jar org.avenir.<pkg>.<Class> -Dconf.path=<props> <in> <out>``
(resource/knn.sh:70-80 and every other runbook).  Here the same invocation is
``python -m avenir_tpu <Class|FQCN> -Dconf.path=<props> <in> <out>`` — same
properties files, same schema JSONs, same in/out directory conventions, with
job counters printed to stderr the way the MR framework printed counter
groups.

The registry maps reference driver class names (short or fully-qualified) to
job factories; jobs expose ``run(in_path, out_path) -> Counters``.
"""

from __future__ import annotations

import sys
from typing import Callable, Dict

from .core.config import JobConfig, load_job_config, parse_cli_args
from .core.metrics import Counters


def _lazy(modname: str, clsname: str) -> Callable[[JobConfig], object]:
    def factory(config: JobConfig):
        return job_class()(config)

    def job_class():
        import importlib
        mod = importlib.import_module(f"avenir_tpu.models.{modname}")
        return getattr(mod, clsname)
    # the class WITHOUT constructing a driver — core.dag probes it for
    # shared-scan fusability (fold_spec) before deciding how to schedule
    factory.job_class = job_class
    return factory


# reference driver class -> (module, job class, config key prefix)
# Prefixes follow the reference's per-job property namespaces (SURVEY §5:
# dtb.*, fia.*, arm.*, mst.* ... with un-prefixed fallback).
JOBS: Dict[str, tuple] = {
    "org.avenir.bayesian.BayesianDistribution": ("bayesian", "BayesianDistribution", ""),
    "org.avenir.bayesian.BayesianPredictor": ("bayesian", "BayesianPredictor", "bp"),
    "org.avenir.markov.MarkovStateTransitionModel": ("markov", "MarkovStateTransitionModel", "mst"),
    "org.avenir.markov.MarkovModelClassifier": ("markov", "MarkovModelClassifier", ""),
    "org.avenir.markov.HiddenMarkovModelBuilder": ("markov", "HiddenMarkovModelBuilder", ""),
    "org.avenir.markov.ViterbiStatePredictor": ("markov", "ViterbiStatePredictor", ""),
    "org.avenir.markov.ProbabilisticSuffixTreeGenerator": ("pst", "ProbabilisticSuffixTreeGenerator", ""),
    "org.avenir.explore.MutualInformation": ("mutual_info", "MutualInformation", ""),
    "org.avenir.explore.CramerCorrelation": ("correlation", "CramerCorrelation", ""),
    "org.avenir.explore.HeterogeneityReductionCorrelation": ("correlation", "HeterogeneityReductionCorrelation", ""),
    "org.avenir.explore.NumericalCorrelation": ("correlation", "NumericalCorrelation", "nco"),
    "org.avenir.explore.BaggingSampler": ("sampler", "BaggingSampler", ""),
    "org.avenir.explore.UnderSamplingBalancer": ("sampler", "UnderSamplingBalancer", ""),
    "org.avenir.discriminant.FisherDiscriminant": ("discriminant", "FisherDiscriminant", ""),
    "org.chombo.mr.NumericalAttrStats": ("discriminant", "NumericalAttrStats", ""),
    # external chombo legs invoked between avenir jobs in reference
    # runbooks (fit.sh:30-41, cust_churn_markov_chain tutorial:26-37,
    # price_optimize_tutorial.txt:41-62)
    "org.chombo.mr.TemporalFilter": ("chombo", "TemporalFilter", "tef"),
    "org.chombo.mr.Projection": ("chombo", "Projection", ""),
    "org.chombo.mr.RunningAggregator": ("chombo", "RunningAggregator", ""),
    "org.avenir.explore.ClassPartitionGenerator": ("tree", "ClassPartitionGenerator", ""),
    "org.avenir.tree.SplitGenerator": ("tree", "SplitGenerator", ""),
    "org.avenir.tree.DecisionTreeBuilder": ("tree", "DecisionTreeBuilder", "dtb"),
    "org.avenir.tree.DataPartitioner": ("tree", "DataPartitioner", ""),
    "org.sifarish.feature.SameTypeSimilarity": ("knn", "SameTypeSimilarity", ""),
    "org.avenir.knn.FeatureCondProbJoiner": ("knn", "FeatureCondProbJoiner", ""),
    "org.avenir.knn.NearestNeighbor": ("knn", "NearestNeighbor", ""),
    "org.avenir.cluster.AgglomerativeGraphical": ("cluster", "AgglomerativeGraphical", ""),
    "org.avenir.association.FrequentItemsApriori": ("association", "FrequentItemsApriori", "fia"),
    "org.avenir.association.AssociationRuleMiner": ("association", "AssociationRuleMiner", "arm"),
    "org.avenir.association.InfrequentItemMarker": ("association", "InfrequentItemMarker", "iim"),
    "org.avenir.regress.LogisticRegressionJob": ("regress", "LogisticRegressionJob", ""),
    "org.avenir.reinforce.GreedyRandomBandit": ("bandit", "GreedyRandomBandit", ""),
    # batch replay of a reward-event log into per-arm posterior state —
    # the byte-equivalence reference for the streaming feedback consumer
    # (avenir_tpu/stream); net-new surface, no reference driver class
    "org.avenir.reinforce.BanditFeedbackAggregator": ("bandit", "BanditFeedbackAggregator", ""),
    "org.avenir.reinforce.AuerDeterministic": ("bandit", "AuerDeterministic", ""),
    "org.avenir.reinforce.SoftMaxBandit": ("bandit", "SoftMaxBandit", ""),
    "org.avenir.reinforce.RandomFirstGreedyBandit": ("bandit", "RandomFirstGreedyBandit", ""),
    "org.avenir.sequence.CandidateGenerationWithSelfJoin": ("sequence", "CandidateGenerationWithSelfJoin", "cgs"),
    "org.avenir.sequence.SequencePositionalCluster": ("sequence", "SequencePositionalCluster", ""),
    "org.avenir.text.WordCounter": ("text", "WordCounter", ""),
    # streaming entry point: positional args are (topologyName, configFile)
    # per the reference main() (ReinforcementLearnerTopology.java:42-47)
    "org.avenir.reinforce.ReinforcementLearnerTopology": ("streaming", "ReinforcementLearnerTopology", ""),
}


def resolve(name: str):
    if name in JOBS:
        return JOBS[name]
    # short-name lookup
    for fq, spec in JOBS.items():
        if fq.rsplit(".", 1)[1] == name:
            return spec
    raise SystemExit(
        f"unknown job: {name}\nknown jobs:\n  " +
        "\n  ".join(sorted(JOBS)))


def register(fqcn: str, module: str, cls: str, prefix: str = "") -> None:
    JOBS[fqcn] = (module, cls, prefix)


def _extract_value_flag(argv, flag: str):
    """Pull ``<flag> <value>`` / ``<flag>=<value>`` out of an arg vector;
    returns (remaining argv, value or None)."""
    out, value, i = [], None, 0
    while i < len(argv):
        a = argv[i]
        if a == flag:
            if i + 1 >= len(argv):
                raise SystemExit(f"{flag} requires an output path")
            value = argv[i + 1]
            i += 2
            continue
        if a.startswith(flag + "="):
            value = a.partition("=")[2]
            if not value:
                raise SystemExit(f"{flag} requires an output path")
            i += 1
            continue
        out.append(a)
        i += 1
    return out, value


def extract_trace_flag(argv):
    """Pull ``--trace <out.json>`` / ``--trace=<out.json>`` out of an arg
    vector; returns (remaining argv, trace path or None)."""
    return _extract_value_flag(argv, "--trace")


def extract_metrics_out_flag(argv):
    """Pull ``--metrics-out <path>`` / ``--metrics-out=<path>`` out of an
    arg vector; returns (remaining argv, path or None).  The flag starts
    the periodic telemetry exporter (core.telemetry): one mergeable
    JSONL snapshot of the global metrics registry per
    ``telemetry.interval.sec``, plus a final one at job exit."""
    return _extract_value_flag(argv, "--metrics-out")


def extract_resume_flag(argv):
    """Pull ``--resume`` out of an arg vector; returns (remaining argv,
    bool).  The flag maps to ``checkpoint.resume=true`` — the job
    restarts from its sidecar checkpoint (core.checkpoint) when one
    exists, or runs from scratch when none does."""
    out = [a for a in argv if a != "--resume"]
    return out, len(out) != len(argv)


def configure_resilience(config) -> None:
    """Apply the resilience-layer config surfaces (retry policy + fault
    injection plan + the io durability strict mode + the flight
    recorder's dump surface + the lock sanitizer) — called by every CLI
    entry point next to the obs configure, BEFORE any engine/server
    construction so ``sanitize.locks=true`` catches every lock."""
    from .core import faultinject, flight, io, resilience, sanitizer
    # sanitizer FIRST: the other configure calls construct lock-bearing
    # singletons (RetryPolicy, FaultInjector), and locks built before
    # enablement stay plain/untracked
    sanitizer.configure_from_config(config)
    resilience.configure_from_config(config)
    faultinject.configure_from_config(config)
    io.configure_from_config(config)
    flight.configure_from_config(config)


def _init_runtime() -> None:
    """Platform pin + x64 enable shared by every CLI entry point: the
    JAX_PLATFORMS env var alone is overridden by site TPU plugins, so an
    ``AVENIR_PLATFORM`` override must go through the config API (same as
    tests/conftest.py)."""
    import os
    plat = os.environ.get("AVENIR_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)
    import avenir_tpu
    avenir_tpu.enable_x64()


def _export_trace(trace_path) -> None:
    """Export the obs tracer as Chrome/Perfetto trace JSON (no-op when
    --trace was not given)."""
    if not trace_path:
        return
    from .core import obs
    n = obs.get_tracer().export_chrome_trace(trace_path)
    print(f"obs: wrote {n} trace events to {trace_path} "
          f"(open in chrome://tracing or ui.perfetto.dev)",
          file=sys.stderr)


def _job_resolver(cls_name: str):
    """``multi`` manifest resolver: job class name -> (factory, prefix)."""
    modname, clsname, prefix = resolve(cls_name)
    return _lazy(modname, clsname), prefix


def multi_main(argv) -> int:
    """``python -m avenir_tpu multi -Dconf.path=<manifest> <in> [<out>]``:
    run every job in the ``multi.jobs`` manifest off ONE streamed ingest
    pass (core.multiscan), writing each job's normal output file.  Jobs
    that cannot fuse (no FoldSpec, mid-stream cap overflow) re-run
    standalone after the fused pass, so the workflow's outputs are
    always complete."""
    argv, trace_path = extract_trace_flag(argv)
    argv, metrics_out = extract_metrics_out_flag(argv)
    argv, resume = extract_resume_flag(argv)
    defines, positional = parse_cli_args(argv)
    if not positional:
        print("expected <input path> [<output base dir>]", file=sys.stderr)
        return 2
    in_path = positional[0]
    out_base = positional[1] if len(positional) > 1 else None

    _init_runtime()
    config = load_job_config(defines, "")
    if resume:
        config.set("checkpoint.resume", "true")
    from .core import obs, telemetry
    from .core.multiscan import run_multi
    from .fleetobs.publisher import publisher_for_job
    obs.configure_from_config(config, force_enable=bool(trace_path))
    # before configure_resilience: the publisher routes flight.dump.dir
    # into the spool feed when fleetobs.spool.dir is set
    publisher = publisher_for_job(config, role="multi")
    configure_resilience(config)
    telemetry.configure_from_config(config)
    exporter = telemetry.exporter_for_job(config, metrics_out)
    if publisher is not None:
        exporter = publisher.attach(exporter, config)
    flusher = telemetry.flusher_for_job(config, trace_path)
    try:
        results = run_multi(config, in_path, out_base, _job_resolver,
                            log=lambda m: print(m, file=sys.stderr))
    except BaseException as exc:
        # a fatal workflow exception still leaves the black box behind
        from .core import flight
        flight.fatal(exc)
        raise
    finally:
        if flusher is not None:
            flusher.stop()
        if exporter is not None:
            exporter.stop()
        _export_trace(trace_path)
    for jid, counters in results.items():
        print(f"--- job {jid}", file=sys.stderr)
        if isinstance(counters, Counters):
            print(counters.format(), file=sys.stderr)
    return 0


def dag_main(argv) -> int:
    """``python -m avenir_tpu dag -Dconf.path=<workflow.properties> <in>
    [<out base>] [--resume]``: run the ``workflow.*`` stage DAG
    (core.dag) — topologically ordered stages, cost-decided shared scans
    for same-input groups, in-memory artifact handoff, and
    stage-granularity checkpoint/resume."""
    argv, trace_path = extract_trace_flag(argv)
    argv, metrics_out = extract_metrics_out_flag(argv)
    argv, resume = extract_resume_flag(argv)
    defines, positional = parse_cli_args(argv)
    if not positional:
        print("expected <input path> [<output base dir>]", file=sys.stderr)
        return 2
    in_path = positional[0]
    out_base = positional[1] if len(positional) > 1 else None

    _init_runtime()
    config = load_job_config(defines, "")
    if resume:
        config.set("checkpoint.resume", "true")
    from .core import obs, telemetry
    from .core.dag import run_workflow
    from .fleetobs.publisher import publisher_for_job
    obs.configure_from_config(config, force_enable=bool(trace_path))
    # before configure_resilience: the publisher routes flight.dump.dir
    # into the spool feed when fleetobs.spool.dir is set
    publisher = publisher_for_job(config, role="dag")
    configure_resilience(config)
    telemetry.configure_from_config(config)
    exporter = telemetry.exporter_for_job(config, metrics_out)
    if publisher is not None:
        exporter = publisher.attach(exporter, config)
    flusher = telemetry.flusher_for_job(config, trace_path)
    try:
        results = run_workflow(config, in_path, out_base, _job_resolver,
                               log=lambda m: print(m, file=sys.stderr))
    except BaseException as exc:
        # a fatal workflow exception still leaves the black box behind
        from .core import flight
        flight.fatal(exc)
        raise
    finally:
        if flusher is not None:
            flusher.stop()
        if exporter is not None:
            exporter.stop()
        _export_trace(trace_path)
    for sid, counters in results.items():
        print(f"--- stage {sid}", file=sys.stderr)
        if isinstance(counters, Counters):
            print(counters.format(), file=sys.stderr)
    return 0


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print("usage: python -m avenir_tpu <JobClass> -Dconf.path=<props> <in> <out>",
              file=sys.stderr)
        print("       python -m avenir_tpu multi -Dconf.path=<manifest.properties> <in> [<out base>]",
              file=sys.stderr)
        print("       python -m avenir_tpu dag -Dconf.path=<workflow.properties> <in> [<out base>]",
              file=sys.stderr)
        print("       python -m avenir_tpu serve -Dconf.path=<serve.properties>",
              file=sys.stderr)
        print("       python -m avenir_tpu stream -Dconf.path=<stream.properties> [--resume]",
              file=sys.stderr)
        print("       python -m avenir_tpu workload --scenario <scenario.properties> [--assert]",
              file=sys.stderr)
        print("       python -m avenir_tpu fleetobs -Dfleetobs.spool.dir=<dir> [--once]",
              file=sys.stderr)
        print("       python -m avenir_tpu fleetobs stitch --spool <dir> [--trace-id X] [--out f.json]",
              file=sys.stderr)
        print("       python -m avenir_tpu router -Drouter.backends=host:p1,host:p2 [-Drouter.port=N]",
              file=sys.stderr)
        print("       python -m avenir_tpu analyze [--strict] [--json report.json] [--rules a,b] [--list]",
              file=sys.stderr)
        print("                                    [--dynamic] [--seeds N] [--baseline findings.json] [--update-baseline] [--no-cache]",
              file=sys.stderr)
        print("known jobs:\n  " + "\n  ".join(sorted(JOBS)), file=sys.stderr)
        return 2

    job_name, rest = argv[0], argv[1:]
    if job_name == "analyze":
        # static-analysis engine (avenir-analyze): the rule catalog over
        # the whole package, text or JSON findings, --strict CI gate
        from .analysis.cli import analyze_main
        return analyze_main(rest)
    if job_name == "multi":
        # shared-scan job fusion (core.multiscan): one streamed ingest
        # pass feeding every job named by the multi.* manifest
        return multi_main(rest)
    if job_name == "dag":
        # cost-based workflow DAG (core.dag): stage scheduling over
        # shared scans with artifact handoff and stage checkpoints
        return dag_main(rest)
    if job_name == "serve":
        # online prediction service (model registry + micro-batching
        # frontend) — net-new surface, no reference driver class
        _init_runtime()
        from .serve.server import serve_main
        return serve_main(rest)
    if job_name == "stream":
        # streaming decision service (avenir_tpu/stream): bandit decide
        # serving + exactly-once Redis-stream feedback folding
        _init_runtime()
        from .stream.service import stream_main
        return stream_main(rest)
    if job_name == "workload":
        # production-shaped workload harness (avenir_tpu/workload):
        # seeded scenario factory + open-loop fleet + SLO-envelope
        # verdicts against the real serve/stream frontends
        _init_runtime()
        from .workload.runner import workload_main
        return workload_main(rest)
    if job_name == "fleetobs":
        # fleet observability plane (avenir_tpu/fleetobs): spool
        # aggregation, fleet SLO boards, trace stitching, incident
        # bundles.  Deliberately NO _init_runtime(): the aggregator is
        # jax-free by design.
        from .fleetobs.aggregator import fleetobs_main
        return fleetobs_main(rest)
    if job_name == "router":
        # fleet router tier (avenir_tpu/serve/fleet): SLO-fed
        # least-loaded dispatch over N backend serving processes, with
        # failover, autoscaling, and residency coordination.  NO
        # _init_runtime(): the router is jax-free by design — it moves
        # bytes and reads feeds, it never scores.
        from .serve.fleet.router import router_main
        return router_main(rest)
    # --trace <out.json>: record core.obs spans for the whole job and
    # export them as Chrome/Perfetto trace_event JSON on exit
    rest, trace_path = extract_trace_flag(rest)
    # --metrics-out <series.jsonl>: periodic mergeable metrics snapshots
    # (core.telemetry) appended for the whole job, final one at exit
    rest, metrics_out = extract_metrics_out_flag(rest)
    # --resume: restart from the job's sidecar checkpoint (core.checkpoint)
    rest, resume = extract_resume_flag(rest)
    # --profile-dir=<dir>: capture a jax.profiler trace of the whole job
    # (SURVEY §5 tracing rebuild note); view with TensorBoard or Perfetto
    profile_dir = None
    filtered = []
    for a in rest:
        if a == "--profile-dir" or a.startswith("--profile-dir="):
            profile_dir = a.partition("=")[2]
            if not profile_dir:
                print("--profile-dir requires --profile-dir=<dir> "
                      "(the space-separated form is not supported)",
                      file=sys.stderr)
                return 2
        else:
            filtered.append(a)
    modname, clsname, prefix = resolve(job_name)
    defines, positional = parse_cli_args(filtered)
    if len(positional) < 2:
        print("expected <input path> <output path>", file=sys.stderr)
        return 2

    _init_runtime()
    config = load_job_config(defines, prefix)
    if resume:
        config.set("checkpoint.resume", "true")
    from .core import obs, telemetry
    obs.configure_from_config(config, force_enable=bool(trace_path))
    configure_resilience(config)
    telemetry.configure_from_config(config)
    exporter = telemetry.exporter_for_job(config, metrics_out)
    flusher = telemetry.flusher_for_job(config, trace_path)
    try:
        # job construction INSIDE the try: a driver __init__ failure
        # (e.g. a missing must() key) must still stop the just-started
        # telemetry threads and export what was recorded
        job = _lazy(modname, clsname)(config)
        if profile_dir:
            import jax
            with jax.profiler.trace(profile_dir):
                result = job.run(positional[0], positional[1])
        else:
            result = job.run(positional[0], positional[1])
    except BaseException as exc:
        # fatal batch-job exception: force one flight dump (black box)
        # before the normal finally-path exports run
        from .core import flight
        flight.fatal(exc)
        raise
    finally:
        # export even when the job raises or is interrupted — a trace of
        # the failing/slow run is the one the user most needs; the
        # telemetry stop takes a final snapshot tick for the same reason
        if flusher is not None:
            flusher.stop()
        if exporter is not None:
            exporter.stop()
        _export_trace(trace_path)
    if isinstance(result, Counters):
        print(result.format(), file=sys.stderr)
        return 0
    return int(result or 0)


if __name__ == "__main__":
    raise SystemExit(main())

"""Bounded LRU caches for compiled/jitted functions.

Every engine keeps a small dict of jitted functions keyed by
(mesh, shapes, constants).  Python 3.7+ dicts preserve insertion order,
so eviction pops the first key; a plain get() would make that FIFO —
a workload alternating among more than ``cap`` distinct configurations
would evict and recompile its hottest function on every call.  These
helpers make hits refresh recency (move-to-end), turning the bound
into a true LRU (advisor finding, round 4).

Thread safety: the serving subsystem (``avenir_tpu.serve``) hits these
caches from its per-model batcher threads while a concurrent warmup or
hot-swap reload populates them, so get/put run under one module-level
lock.  The pop+reinsert and evict-while-over-cap sequences are each a
handful of dict ops — a single shared lock is cheaper than per-cache
locks and cannot deadlock (no callback runs under it).  Compilation
itself happens OUTSIDE the lock (callers build the value first, then
put), so a slow XLA compile never serializes unrelated cache traffic.
"""

from __future__ import annotations

import threading
from typing import Any, Optional

_DEFAULT_CAP = 4

_LOCK = threading.Lock()


def bounded_cache_get(cache: dict, key) -> Optional[Any]:
    """Return ``cache[key]`` (refreshing its recency) or None."""
    with _LOCK:
        val = cache.pop(key, None)
        if val is not None:
            cache[key] = val        # re-insert: now most recently used
        return val


def bounded_cache_put(cache: dict, key, value,
                      cap: int = _DEFAULT_CAP) -> None:
    """Insert ``key -> value``, evicting the least recently used entry
    once the cache holds ``cap`` items."""
    with _LOCK:
        cache.pop(key, None)
        while len(cache) >= cap:
            cache.pop(next(iter(cache)))
        cache[key] = value


def bounded_cache_clear(cache: dict) -> None:
    """Drop every entry (under the same lock the readers use)."""
    with _LOCK:
        cache.clear()

"""Shared small utilities (cache bounding, etc.)."""

from .caches import bounded_cache_get, bounded_cache_put

__all__ = ["bounded_cache_get", "bounded_cache_put"]

/* Native CSV ingest kernel: delimited text buffer -> typed columns.
 *
 * This is the framework's runtime-side replacement for the per-record text
 * parsing the reference delegates to Hadoop's LineRecordReader + per-mapper
 * String.split (every mapper, e.g.
 * src/main/java/org/avenir/bayesian/BayesianDistribution.java:137-143).  On
 * TPU the compute path is XLA; the ingest path is host-bound, so it is
 * implemented natively: two passes over the raw byte buffer, the first to
 * validate rectangularity and size the outputs, the second to parse fields
 * straight into preallocated NumPy buffers (int64 / float64 / fixed-width
 * bytes) with zero intermediate Python objects.
 *
 * Called from avenir_tpu/native/__init__.py via ctypes.  Returns negative
 * codes instead of raising so the Python caller can fall back to the
 * pure-NumPy path on any malformed input.
 */

#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <stdlib.h>

/* Pass 1: scan the buffer.  Counts non-empty lines, verifies every line has
 * exactly n_cols fields, and records the maximum field width per column
 * (used to size fixed-width bytes outputs).  Returns the row count, or -1
 * on a ragged line / column overflow. */
long long csv_scan(const char *buf, long long len, char delim, int n_cols,
                   int *max_width) {
    long long nrows = 0, i = 0;
    while (i < len) {
        if (buf[i] == '\n') { i++; continue; }
        int col = 0;
        long long fstart = i;
        for (;;) {
            if (i == len || buf[i] == '\n' || buf[i] == delim) {
                long long end = i;
                if (end > fstart && buf[end - 1] == '\r'
                    && (i == len || buf[i] == '\n'))
                    end--; /* CRLF: strip the CR at end of line only */
                if (col >= n_cols) return -1;
                long long w = end - fstart;
                if (w > max_width[col]) max_width[col] = (int)w;
                col++;
                if (i == len) break;
                char c = buf[i];
                i++;
                if (c == '\n') break;
                fstart = i;
            } else {
                i++;
            }
        }
        if (col != n_cols) return -1;
        nrows++;
    }
    return nrows;
}

/* Field parse helpers.  Leading/trailing blanks tolerated (matches Java's
 * trim-free Integer.parseInt failure behavior closely enough: junk -> error). */
static int parse_int_field(const char *p, const char *e, long long *out) {
    while (p < e && (*p == ' ' || *p == '\t')) p++;
    int neg = 0;
    if (p < e && (*p == '-' || *p == '+')) { neg = (*p == '-'); p++; }
    if (p == e) return -1;
    long long v = 0;
    for (; p < e; p++) {
        char c = *p;
        if (c < '0' || c > '9') {
            const char *q = p;
            while (q < e && (*q == ' ' || *q == '\t')) q++;
            if (q != e) return -1;
            break;
        }
        v = v * 10 + (c - '0');
    }
    *out = neg ? -v : v;
    return 0;
}

static int parse_float_field(const char *p, const char *e, double *out) {
    char tmp[64];
    long long w = e - p;
    if (w <= 0 || w >= (long long)sizeof(tmp)) return -1;
    memcpy(tmp, p, (size_t)w);
    tmp[w] = 0;
    char *endp;
    double d = strtod(tmp, &endp);
    while (*endp == ' ' || *endp == '\t') endp++;
    if (endp == tmp || *endp != 0) return -1;
    *out = d;
    return 0;
}

/* ------------------------------------------------------------------ */
/* Categorical hash table: first-seen code assignment over (ptr,len)
 * byte-string keys pointing into the input buffer (no copies).        */

typedef struct {
    long long *start;   /* caller-provided: uniq value byte offsets  */
    int *len;           /* caller-provided: uniq value byte lengths  */
    int n;              /* uniques so far                            */
    int cap;            /* capacity of start/len                     */
    int *slots;         /* open-addressed table: uniq index + 1      */
    int n_slots;        /* power of two                              */
} CatTable;

static unsigned long long hash_bytes(const char *p, int len) {
    unsigned long long h = 1469598103934665603ULL; /* FNV-1a */
    for (int i = 0; i < len; i++) {
        h ^= (unsigned char)p[i];
        h *= 1099511628211ULL;
    }
    return h;
}

static int cat_init(CatTable *t, long long *start, int *len, int cap) {
    t->start = start;
    t->len = len;
    t->n = 0;
    t->cap = cap;
    t->n_slots = 4096;
    t->slots = (int *)calloc((size_t)t->n_slots, sizeof(int));
    return t->slots ? 0 : -1;
}

static int cat_grow(CatTable *t, const char *buf) {
    int n_new = t->n_slots * 2;
    int *slots = (int *)calloc((size_t)n_new, sizeof(int));
    if (!slots) return -1;
    for (int k = 0; k < t->n; k++) {
        unsigned long long h =
            hash_bytes(buf + t->start[k], t->len[k]) & (n_new - 1);
        while (slots[h]) h = (h + 1) & (n_new - 1);
        slots[h] = k + 1;
    }
    free(t->slots);
    t->slots = slots;
    t->n_slots = n_new;
    return 0;
}

/* Returns the first-seen code for the field, or -1 (capacity) / -2 (oom). */
static int cat_code(CatTable *t, const char *buf, const char *p, int flen) {
    if ((long long)t->n * 10 >= (long long)t->n_slots * 7)
        if (cat_grow(t, buf)) return -2;
    unsigned long long h = hash_bytes(p, flen) & (t->n_slots - 1);
    while (t->slots[h]) {
        int idx = t->slots[h] - 1;
        if (t->len[idx] == flen && !memcmp(buf + t->start[idx], p, (size_t)flen))
            return idx;
        h = (h + 1) & (t->n_slots - 1);
    }
    if (t->n >= t->cap) return -1;
    t->start[t->n] = p - buf;
    t->len[t->n] = flen;
    t->slots[h] = ++t->n;
    return t->n - 1;
}

/* Schema-aware single-pass encode: the whole DatasetEncoder hot path.
 *
 * Per file column (size n_cols):
 *   col_type: 0 skip | 1 bucket-int | 2 float | 3 bytes | 4 categorical
 *   feat_idx: destination column j in x/values; -2 routes a categorical
 *             column's codes to ycol (the class attribute); -1 unused
 *             (bytes columns use bytes_out instead)
 *   bucket_w: divisor for type 1 (Java semantics: C '/' truncates toward
 *             zero, matching BayesianDistribution.java:153)
 * Outputs:
 *   x[n_rows, F] int32: bin index / categorical code per feature column
 *   values[n_rows, F] double: raw numeric value (types 1 and 2)
 *   ycol[n_rows] int32: class codes (feat_idx == -2)
 *   bytes_out[col]: fixed-width byte strings (type 3), width bytes_width[col]
 *   uniq_start/uniq_len[col * max_uniq + k]: k-th first-seen unique of a
 *     categorical column (byte range into buf); n_uniq[col] = count
 * Returns 0, or -2 unparseable numeric / -3 max_uniq exceeded / -4 oom /
 * -5 ragged line (column count != n_cols).  The ragged check runs here,
 * not only in csv_scan, because callers supplying a pre-counted row hint
 * skip the scan pass -- without it a short line would silently leave
 * zero/garbage cells and an extra field would index past the spec arrays.
 */
static int encode_range(const char *buf, long long start, long long len,
                        char delim, int n_cols,
                        const int *col_type, const int *feat_idx,
                        const long long *bucket_w, int F,
                        long long row_base, long long row_limit,
                        int32_t *x, double *values, int32_t *ycol,
                        void **bytes_out, const int *bytes_width,
                        CatTable *tables) {
    int rc = 0;
    long long row = row_base, i = start;
    while (!rc && i < len && row < row_limit) {
        if (buf[i] == '\n') { i++; continue; }
        int col = 0;
        long long fstart = i;
        for (;;) {
            if (i == len || buf[i] == '\n' || buf[i] == delim) {
                long long end = i;
                if (end > fstart && buf[end - 1] == '\r'
                    && (i == len || buf[i] == '\n'))
                    end--;
                if (col >= n_cols) { rc = -5; break; }
                int t = col_type[col];
                if (t == 1) {
                    long long v;
                    if (parse_int_field(buf + fstart, buf + end, &v)) {
                        rc = -2; break;
                    }
                    int j = feat_idx[col];
                    x[row * F + j] = (int32_t)(v / bucket_w[col]);
                    values[row * F + j] = (double)v;
                } else if (t == 2) {
                    double d;
                    if (parse_float_field(buf + fstart, buf + end, &d)) {
                        rc = -2; break;
                    }
                    values[row * F + feat_idx[col]] = d;
                } else if (t == 3) {
                    int w = bytes_width[col];
                    long long fl = end - fstart;
                    char *dst = (char *)bytes_out[col] + row * w;
                    if (fl > w) fl = w;
                    memcpy(dst, buf + fstart, (size_t)fl);
                    memset(dst + fl, 0, (size_t)(w - fl));
                } else if (t == 4) {
                    int code = cat_code(&tables[col], buf, buf + fstart,
                                        (int)(end - fstart));
                    if (code < 0) { rc = code == -1 ? -3 : -4; break; }
                    if (feat_idx[col] == -2)
                        ycol[row] = code;
                    else
                        x[row * F + feat_idx[col]] = code;
                }
                col++;
                if (i == len) break;
                char c = buf[i];
                i++;
                if (c == '\n') break;
                fstart = i;
            } else {
                i++;
            }
        }
        if (!rc && col != n_cols) rc = -5;
        row++;
    }
    return rc;
}


int csv_encode(const char *buf, long long len, char delim, int n_cols,
               const int *col_type, const int *feat_idx,
               const long long *bucket_w, int F, long long n_rows,
               int32_t *x, double *values, int32_t *ycol,
               void **bytes_out, const int *bytes_width,
               long long *uniq_start, int *uniq_len, int *n_uniq,
               int max_uniq) {
    CatTable *tables = (CatTable *)calloc((size_t)n_cols, sizeof(CatTable));
    if (!tables) return -4;
    int rc = 0;
    for (int c = 0; c < n_cols && !rc; c++)
        if (col_type[c] == 4)
            if (cat_init(&tables[c], uniq_start + (long long)c * max_uniq,
                         uniq_len + (long long)c * max_uniq, max_uniq))
                rc = -4;
    if (!rc)
        rc = encode_range(buf, 0, len, delim, n_cols, col_type, feat_idx,
                          bucket_w, F, 0, n_rows, x, values, ycol,
                          bytes_out, bytes_width, tables);
    for (int c = 0; c < n_cols; c++) {
        if (col_type[c] == 4) {
            n_uniq[c] = tables[c].n;
            free(tables[c].slots);
        }
    }
    free(tables);
    return rc;
}

/* ------------------------------------------------------------------ */
/* Multithreaded encode.
 *
 * Chunk the buffer at line boundaries; each thread encodes its rows with
 * THREAD-LOCAL categorical tables; then local vocabularies merge into the
 * global first-seen tables IN THREAD ORDER — which reproduces the serial
 * first-seen code assignment exactly, because every value a later chunk
 * contributes first-occurs after all occurrences in earlier chunks — and a
 * final parallel pass remaps local codes to global ones.               */

typedef struct {
    const char *buf;
    long long start, end;        /* byte range (line-aligned)           */
    long long row_base, n_rows;  /* global row offset / rows in chunk   */
    char delim;
    int n_cols;
    const int *col_type;
    const int *feat_idx;
    const long long *bucket_w;
    int F;
    int32_t *x;
    double *values;
    int32_t *ycol;
    void **bytes_out;
    const int *bytes_width;
    CatTable *tables;            /* thread-local, n_cols entries        */
    int *remap;                  /* [n_cat * max_uniq] local->global    */
    const int *cat_slot;         /* file col -> cat scratch slot (-1)   */
    int max_uniq;
    int rc;
} EncodeTask;

static void *count_worker(void *arg) {
    EncodeTask *t = (EncodeTask *)arg;
    long long n = 0;
    const char *p = t->buf + t->start, *e = t->buf + t->end;
    while (p < e) {
        const char *nl = (const char *)memchr(p, '\n', (size_t)(e - p));
        if (!nl) { if (e > p) n++; break; }
        if (nl > p) n++;          /* skip empty lines, matching csv_scan */
        p = nl + 1;
    }
    t->n_rows = n;
    return 0;
}

static void *encode_worker(void *arg) {
    EncodeTask *t = (EncodeTask *)arg;
    t->rc = encode_range(t->buf, t->start, t->end, t->delim, t->n_cols,
                         t->col_type, t->feat_idx, t->bucket_w, t->F,
                         t->row_base, t->row_base + t->n_rows,
                         t->x, t->values, t->ycol, t->bytes_out,
                         t->bytes_width, t->tables);
    return 0;
}

static void *remap_worker(void *arg) {
    EncodeTask *t = (EncodeTask *)arg;
    for (int c = 0; c < t->n_cols; c++) {
        if (t->col_type[c] != 4) continue;
        const int *rm = t->remap + (long long)t->cat_slot[c] * t->max_uniq;
        int j = t->feat_idx[c];
        if (j == -2) {
            for (long long r = t->row_base; r < t->row_base + t->n_rows; r++)
                t->ycol[r] = rm[t->ycol[r]];
        } else {
            for (long long r = t->row_base; r < t->row_base + t->n_rows; r++)
                t->x[r * t->F + j] = rm[t->x[r * t->F + j]];
        }
    }
    return 0;
}

int csv_encode_mt(const char *buf, long long len, char delim, int n_cols,
                  const int *col_type, const int *feat_idx,
                  const long long *bucket_w, int F, long long n_rows,
                  int32_t *x, double *values, int32_t *ycol,
                  void **bytes_out, const int *bytes_width,
                  long long *uniq_start, int *uniq_len, int *n_uniq,
                  int max_uniq, int n_threads) {
    if (n_threads < 2)
        return csv_encode(buf, len, delim, n_cols, col_type, feat_idx,
                          bucket_w, F, n_rows, x, values, ycol, bytes_out,
                          bytes_width, uniq_start, uniq_len, n_uniq,
                          max_uniq);
    int T = n_threads;
    /* scratch only for the categorical columns (not every file column) */
    int *cat_slot = (int *)malloc((size_t)n_cols * sizeof(int));
    int n_cat = 0;
    if (cat_slot)
        for (int c = 0; c < n_cols; c++)
            cat_slot[c] = (col_type[c] == 4) ? n_cat++ : -1;
    long long per_t = (long long)(n_cat ? n_cat : 1) * max_uniq;
    EncodeTask *tasks = (EncodeTask *)calloc((size_t)T, sizeof(EncodeTask));
    pthread_t *tids = (pthread_t *)calloc((size_t)T, sizeof(pthread_t));
    long long *lstart = (long long *)malloc(
        (size_t)T * per_t * sizeof(long long));
    int *llen = (int *)malloc((size_t)T * per_t * sizeof(int));
    int *remaps = (int *)malloc((size_t)T * per_t * sizeof(int));
    CatTable *all_tables =
        (CatTable *)calloc((size_t)T * n_cols, sizeof(CatTable));
    int rc = 0;
    if (!cat_slot || !tasks || !tids || !lstart || !llen || !remaps
        || !all_tables)
        rc = -4;

    /* line-aligned chunk boundaries */
    long long pos = 0;
    for (int t = 0; t < T && !rc; t++) {
        EncodeTask *tk = &tasks[t];
        tk->buf = buf; tk->delim = delim; tk->n_cols = n_cols;
        tk->col_type = col_type; tk->feat_idx = feat_idx;
        tk->bucket_w = bucket_w; tk->F = F;
        tk->x = x; tk->values = values; tk->ycol = ycol;
        tk->bytes_out = bytes_out; tk->bytes_width = bytes_width;
        tk->max_uniq = max_uniq;
        tk->tables = all_tables + (long long)t * n_cols;
        tk->remap = remaps + (long long)t * per_t;
        tk->cat_slot = cat_slot;
        tk->start = pos;
        long long target = len * (t + 1) / T;
        if (target < pos) target = pos;
        if (t == T - 1) target = len;
        else {
            const char *nl = (const char *)memchr(buf + target, '\n',
                                                  (size_t)(len - target));
            target = nl ? (nl - buf) + 1 : len;
        }
        tk->end = target;
        pos = target;
        for (int c = 0; c < n_cols && !rc; c++)
            if (col_type[c] == 4) {
                long long off = (long long)t * per_t
                    + (long long)cat_slot[c] * max_uniq;
                if (cat_init(&tk->tables[c], lstart + off, llen + off,
                             max_uniq))
                    rc = -4;
            }
    }

    /* round 1: count rows per chunk, prefix-sum into row bases */
    if (!rc) {
        int created = 0;
        for (int t = 0; t < T; t++, created++)
            if (pthread_create(&tids[t], 0, count_worker, &tasks[t])) {
                rc = -4; break;
            }
        for (int t = 0; t < created; t++) pthread_join(tids[t], 0);
    }
    if (!rc) {
        long long base = 0;
        for (int t = 0; t < T; t++) {
            tasks[t].row_base = base;
            base += tasks[t].n_rows;
        }
        if (base != n_rows) rc = -1;
    }

    /* round 2: parallel encode with thread-local vocabularies */
    if (!rc) {
        int created = 0;
        for (int t = 0; t < T; t++, created++)
            if (pthread_create(&tids[t], 0, encode_worker, &tasks[t])) {
                rc = -4; break;
            }
        for (int t = 0; t < created; t++) {
            pthread_join(tids[t], 0);
            if (tasks[t].rc) rc = tasks[t].rc;
        }
        if (created < T && !rc) rc = -4;
    }

    /* serial merge in thread order = global first-seen order */
    if (!rc) {
        CatTable *gtab = (CatTable *)calloc((size_t)n_cols, sizeof(CatTable));
        if (!gtab) rc = -4;
        for (int c = 0; c < n_cols && !rc; c++) {
            if (col_type[c] != 4) continue;
            if (cat_init(&gtab[c], uniq_start + (long long)c * max_uniq,
                         uniq_len + (long long)c * max_uniq, max_uniq)) {
                rc = -4; break;
            }
            for (int t = 0; t < T && !rc; t++) {
                CatTable *lt = &tasks[t].tables[c];
                int *rm = tasks[t].remap
                    + (long long)cat_slot[c] * max_uniq;
                for (int k = 0; k < lt->n; k++) {
                    int code = cat_code(&gtab[c], buf, buf + lt->start[k],
                                        lt->len[k]);
                    if (code < 0) { rc = code == -1 ? -3 : -4; break; }
                    rm[k] = code;
                }
            }
            n_uniq[c] = gtab[c].n;
        }
        if (gtab) {
            for (int c = 0; c < n_cols; c++)
                if (col_type[c] == 4) free(gtab[c].slots);
            free(gtab);
        }
    }

    /* round 3: parallel local->global code remap */
    if (!rc) {
        int created = 0;
        for (int t = 0; t < T; t++, created++)
            if (pthread_create(&tids[t], 0, remap_worker, &tasks[t])) {
                rc = -4; break;
            }
        for (int t = 0; t < created; t++) pthread_join(tids[t], 0);
        if (created < T && !rc) rc = -4;
    }

    if (all_tables)
        for (long long i = 0; i < (long long)T * n_cols; i++)
            free(all_tables[i].slots);
    free(all_tables); free(remaps); free(llen); free(lstart);
    free(tids); free(tasks); free(cat_slot);
    return rc;
}

/* Pass 2: parse fields into preallocated column buffers.
 *
 * col_type per column: 0 = skip, 1 = int64, 2 = float64, 3 = fixed-width
 * bytes (width[col] from csv_scan; short fields are zero-padded, matching
 * NumPy 'S' semantics).  outs[col] points at the column's buffer (NULL for
 * skipped columns).  Returns 0, or -2 on an unparseable numeric field. */
int csv_parse(const char *buf, long long len, char delim, int n_cols,
              const int *col_type, const int *width, void **outs,
              long long n_rows) {
    long long row = 0, i = 0;
    while (i < len && row < n_rows) {
        if (buf[i] == '\n') { i++; continue; }
        int col = 0;
        long long fstart = i;
        for (;;) {
            if (i == len || buf[i] == '\n' || buf[i] == delim) {
                long long end = i;
                if (end > fstart && buf[end - 1] == '\r'
                    && (i == len || buf[i] == '\n'))
                    end--;
                int t = col_type[col];
                if (t == 1) {
                    if (parse_int_field(buf + fstart, buf + end,
                                        &((long long *)outs[col])[row]))
                        return -2;
                } else if (t == 2) {
                    if (parse_float_field(buf + fstart, buf + end,
                                          &((double *)outs[col])[row]))
                        return -2;
                } else if (t == 3) {
                    int w = width[col];
                    long long fl = end - fstart;
                    char *dst = (char *)outs[col] + (long long)row * w;
                    if (fl > w) fl = w;
                    memcpy(dst, buf + fstart, (size_t)fl);
                    memset(dst + fl, 0, (size_t)(w - fl));
                }
                col++;
                if (i == len) break;
                char c = buf[i];
                i++;
                if (c == '\n') break;
                fstart = i;
            } else {
                i++;
            }
        }
        row++;
    }
    return 0;
}

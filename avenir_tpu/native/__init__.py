"""Native runtime components (C, loaded via ctypes).

The compute path of the framework is XLA-compiled JAX; the host runtime
around it — here, CSV ingest — is native C.  The reference's equivalent
layer is Hadoop's record readers + JVM string handling (SURVEY §2.0: the
reference has no native code of its own; its "native" layer is the JVM).

The kernel source lives next to this file and is compiled on demand with the
system C compiler into ``_csv_ingest.so`` (rebuilt when the source is newer).
Every entry point degrades gracefully: if no compiler is available or the
input doesn't fit the fast path, callers fall back to the pure-NumPy ingest
in ``core.binning``/``core.io``.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "csv_ingest.c")
_SO = os.path.join(_HERE, "_csv_ingest.so")

_lock = threading.Lock()
_lib = None
_lib_failed = False

# column type codes shared with csv_ingest.c
SKIP, INT64, FLOAT64, BYTES = 0, 1, 2, 3

# buffers at least this large take the multithreaded encode path
MT_MIN_BYTES = 4 << 20
# thread count override (None = min(8, cores)); tests force >1 so the
# pthread path is exercised even on single-core hosts
MT_THREADS = None
BUCKET, FLOATVAL, CAT = 1, 2, 4      # csv_encode column roles
Y_DEST = -2                          # feat_idx routing a CAT column to ycol


def _cc_run(cc: str):
    """One compiler invocation (run under ``with_retries``: a transient
    OSError — fork failure, tmpfs hiccup — backs off and reattempts
    before the next compiler is tried)."""
    return subprocess.run(
        [cc, "-O3", "-pthread", "-shared", "-fPIC", "-o", _SO, _SRC],
        capture_output=True, timeout=120)


def _compile() -> bool:
    from ..core.resilience import with_retries

    for cc in ("cc", "gcc", "g++"):
        try:
            proc = with_retries(_cc_run, cc, op="native.compile")
        except (OSError, subprocess.TimeoutExpired):
            continue
        if proc.returncode == 0:
            return True
    return False


def get_lib():
    """The loaded C kernel, or None if it can't be built on this host."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                if not _compile():
                    raise OSError("no working C compiler")
            lib = ctypes.CDLL(_SO)
            lib.csv_scan.restype = ctypes.c_longlong
            lib.csv_scan.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char,
                ctypes.c_int, ctypes.POINTER(ctypes.c_int)]
            lib.csv_parse.restype = ctypes.c_int
            lib.csv_parse.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char,
                ctypes.c_int, ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_int),
                ctypes.POINTER(ctypes.c_void_p), ctypes.c_longlong]
            lib.csv_encode.restype = ctypes.c_int
            lib.csv_encode.argtypes = [
                ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char,
                ctypes.c_int,                        # n_cols
                ctypes.POINTER(ctypes.c_int),        # col_type
                ctypes.POINTER(ctypes.c_int),        # feat_idx
                ctypes.POINTER(ctypes.c_longlong),   # bucket_w
                ctypes.c_int, ctypes.c_longlong,     # F, n_rows
                ctypes.c_void_p, ctypes.c_void_p,    # x, values
                ctypes.c_void_p,                     # ycol
                ctypes.POINTER(ctypes.c_void_p),     # bytes_out
                ctypes.POINTER(ctypes.c_int),        # bytes_width
                ctypes.c_void_p, ctypes.c_void_p,    # uniq_start, uniq_len
                ctypes.c_void_p, ctypes.c_int]       # n_uniq, max_uniq
            lib.csv_encode_mt.restype = ctypes.c_int
            lib.csv_encode_mt.argtypes = (list(lib.csv_encode.argtypes)
                                          + [ctypes.c_int])  # n_threads
            _lib = lib
        except Exception as e:  # pragma: no cover - environment-dependent
            print(f"avenir_tpu.native: C ingest unavailable ({e}); "
                  f"using NumPy fallback", file=sys.stderr)
            _lib_failed = True
    return _lib


def _read_part(fp: str) -> bytes:
    """One part-file read attempt (a ``read`` fault-injection point,
    run under ``with_retries`` so transient I/O errors back off)."""
    from ..core import faultinject
    fi = faultinject.get_injector()
    if fi is not None:
        fi.fire("read")
    with open(fp, "rb") as fh:
        return fh.read()


def _read_buffer(path: str) -> bytes:
    """Concatenate a file or every part file of a job-output directory
    (the bulk-ingest read: every chunked scan starts here, so this is
    the retried read on the ingest hot path)."""
    from ..core.io import _input_files
    from ..core.resilience import with_retries
    parts = []
    for fp in _input_files(path):
        parts.append(with_retries(_read_part, fp, op="ingest.read"))
    return b"\n".join(parts)


def parse_csv_columns(path: str, col_types: Sequence[int], delim: str = ","
                      ) -> Optional[Tuple[int, Dict[int, np.ndarray]]]:
    """Parse a delimited file (or part-file dir) into typed NumPy columns.

    ``col_types[i]`` is SKIP/INT64/FLOAT64/BYTES for column ordinal ``i``;
    trailing file columns beyond ``len(col_types)`` are not allowed (the
    caller sizes ``col_types`` to the file's column count).  Returns
    ``(n_rows, {ordinal: array})`` or None when the fast path does not apply
    (no compiler, ragged rows, unparseable numerics) — callers then fall
    back to the NumPy path.
    """
    lib = get_lib()
    if lib is None or len(delim) != 1:
        return None
    return parse_csv_columns_buffer(_read_buffer(path), col_types, delim)


def parse_csv_columns_buffer(buf: bytes, col_types: Sequence[int],
                             delim: str = ","
                             ) -> Optional[Tuple[int, Dict[int, np.ndarray]]]:
    """``parse_csv_columns`` over an in-memory buffer — the per-chunk
    form the shared-scan engine uses to extract just the columns a job
    needs without materializing the whole field matrix."""
    lib = get_lib()
    if lib is None or len(delim) != 1:
        return None
    n_cols = len(col_types)
    bdelim = ctypes.c_char(delim.encode())
    widths = (ctypes.c_int * n_cols)(*([0] * n_cols))
    n_rows = lib.csv_scan(buf, len(buf), bdelim, n_cols, widths)
    if n_rows < 0:
        return None

    cols: Dict[int, np.ndarray] = {}
    outs = (ctypes.c_void_p * n_cols)(*([None] * n_cols))
    ctypes_types = (ctypes.c_int * n_cols)(*col_types)
    for j, t in enumerate(col_types):
        if t == INT64:
            a = np.empty(n_rows, dtype=np.int64)
        elif t == FLOAT64:
            a = np.empty(n_rows, dtype=np.float64)
        elif t == BYTES:
            a = np.empty(n_rows, dtype=f"S{max(int(widths[j]), 1)}")
        else:
            continue
        cols[j] = a
        outs[j] = a.ctypes.data
    rc = lib.csv_parse(buf, len(buf), bdelim, n_cols, ctypes_types, widths,
                       outs, n_rows)
    if rc != 0:
        return None
    return int(n_rows), cols


def encode_schema(path: str, col_specs: Sequence[Tuple[int, int, int]],
                  n_file_cols: int, n_feat: int, has_class: bool,
                  id_ordinal: int = -1, delim: str = ",",
                  max_uniq: int = 1 << 16):
    """Single-pass schema-aware encode: the DatasetEncoder hot path in C.

    ``col_specs`` is ``(ordinal, role, arg)`` per schema column where role is
    BUCKET (arg = bucket width), FLOATVAL, or CAT, and ``arg`` for CAT is the
    destination feature index (or Y_DEST for the class attribute). BUCKET and
    FLOATVAL specs carry their feature index in ``arg2``... — concretely each
    spec is ``(file_ordinal, role, feat_idx, extra)`` with ``extra`` the
    bucket width for BUCKET columns.

    Returns ``(n_rows, x, values, y, ids, cat_uniques)`` where
    ``cat_uniques[ordinal]`` is the first-seen list of raw byte values of
    each categorical column (codes in ``x``/``y`` index into it), or None
    when the fast path does not apply.
    """
    lib = get_lib()
    if lib is None or len(delim) != 1:
        return None
    buf = _read_buffer(path)
    return encode_schema_buffer(buf, col_specs, n_file_cols, n_feat,
                                has_class, id_ordinal, delim, max_uniq)


def encode_schema_buffer(buf: bytes, col_specs, n_file_cols: int,
                         n_feat: int, has_class: bool, id_ordinal: int = -1,
                         delim: str = ",", max_uniq: int = 1 << 16,
                         n_rows_hint: Optional[int] = None,
                         n_threads: Optional[int] = None):
    """``encode_schema`` over an in-memory buffer — the chunked-ingest
    entry point (the caller splits a file at line boundaries and encodes
    each chunk while earlier chunks are counting on device).
    ``n_rows_hint`` (an exact line count) skips the csv_scan sizing pass;
    it is only honored when no bytes (id) column needs width metering.
    ``n_threads`` forces the inner pthread fan-out (the parallel-parse
    worker pool passes 1 so chunk-level and byte-range-level parallelism
    don't multiply); None keeps the size-based heuristic below."""
    lib = get_lib()
    if lib is None or len(delim) != 1:
        return None
    bdelim = ctypes.c_char(delim.encode())

    col_type = [SKIP] * n_file_cols
    feat_idx = [-1] * n_file_cols
    bucket_w = [1] * n_file_cols
    for ordinal, role, fj, extra in col_specs:
        if ordinal >= n_file_cols:
            return None
        col_type[ordinal] = role
        feat_idx[ordinal] = fj
        if role == BUCKET:
            if extra <= 0:
                return None
            bucket_w[ordinal] = extra

    widths = (ctypes.c_int * n_file_cols)(*([0] * n_file_cols))
    if n_rows_hint is not None and id_ordinal < 0:
        n_rows = n_rows_hint        # widths only meter bytes (id) columns
    else:
        n_rows = lib.csv_scan(buf, len(buf), bdelim, n_file_cols, widths)
    if n_rows < 0:
        return None

    ids = None
    bytes_out = (ctypes.c_void_p * n_file_cols)(*([None] * n_file_cols))
    if id_ordinal >= 0:
        col_type[id_ordinal] = BYTES
        ids = np.empty(n_rows, dtype=f"S{max(int(widths[id_ordinal]), 1)}")
        bytes_out[id_ordinal] = ids.ctypes.data

    x = np.zeros((n_rows, n_feat), dtype=np.int32)
    values = np.zeros((n_rows, n_feat), dtype=np.float64)
    y = np.empty(n_rows, dtype=np.int32) if has_class else None
    cat_ordinals = [o for o, t, _, _ in col_specs if t == CAT]
    uniq_start = np.zeros((n_file_cols, max_uniq), dtype=np.int64) \
        if cat_ordinals else np.zeros((1, 1), dtype=np.int64)
    uniq_len = np.zeros_like(uniq_start, dtype=np.int32)
    n_uniq = np.zeros(n_file_cols, dtype=np.int32)

    # multithreaded encode for large buffers; the local-vocab memory is
    # T * n_cat * max_uniq * 16B, so the big-vocab retry stays
    # single-threaded and the thread count scales down with the
    # categorical column count to cap transient scratch at ~128 MB
    # (a many-categorical schema would otherwise allocate hundreds of MB)
    forced_threads = n_threads
    if n_threads is None:
        n_threads = 1
        if len(buf) >= MT_MIN_BYTES and max_uniq <= (1 << 16):
            n_threads = MT_THREADS or min(8, os.cpu_count() or 1)
            scratch_budget = 128 << 20
            per_thread = max(len(cat_ordinals), 1) * max_uniq * 16
            n_threads = max(min(n_threads, scratch_budget // per_thread), 1)
    else:
        n_threads = max(int(n_threads), 1)
    rc = lib.csv_encode_mt(
        buf, len(buf), bdelim, n_file_cols,
        (ctypes.c_int * n_file_cols)(*col_type),
        (ctypes.c_int * n_file_cols)(*feat_idx),
        (ctypes.c_longlong * n_file_cols)(*bucket_w),
        n_feat, n_rows,
        x.ctypes.data, values.ctypes.data,
        y.ctypes.data if y is not None else None,
        bytes_out, widths,
        uniq_start.ctypes.data, uniq_len.ctypes.data, n_uniq.ctypes.data,
        uniq_start.shape[1], n_threads)
    if rc == -3 and max_uniq < (1 << 22):   # vocab overflow: one retry, 64x
        return encode_schema_buffer(buf, col_specs, n_file_cols, n_feat,
                                    has_class, id_ordinal, delim,
                                    max_uniq=1 << 22,
                                    n_threads=forced_threads)
    if rc != 0:
        return None

    cat_uniques: Dict[int, List[bytes]] = {}
    for o in cat_ordinals:
        k = int(n_uniq[o])
        cat_uniques[o] = [bytes(buf[int(s):int(s) + int(l)])
                          for s, l in zip(uniq_start[o, :k], uniq_len[o, :k])]
    return int(n_rows), x, values, y, ids, cat_uniques
